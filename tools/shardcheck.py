"""shardcheck — compile-time sharding + per-chip memory regression gate.

Correctness-at-scale for the unified sharding API
(``paddle_tpu.distributed.shard``) must be checkable with NO TPU
attached: this tool AOT-compiles sharded train/predict steps against
abstract mesh topologies (the ``_ernie10b_plan`` trick — on a real
``jax.experimental.topologies`` TPU topology when one is requested and
available, else the local forced-CPU virtual devices), extracts the
per-chip memory plan and per-parameter shardings from the compiled
artifact, projects model-state bytes to the plan's TARGET chip count
from the spec tree, and gates everything against a committed baseline
JSON (pdlint/perfci style) — so every future sharding change is
validated at compile time in CI.

Usage:

    python tools/shardcheck.py                       # gate all plans
    python tools/shardcheck.py --plans ernie10b      # one plan
    python tools/shardcheck.py --json                # machine-readable
    python tools/shardcheck.py --write-baseline      # re-baseline
    python tools/shardcheck.py --tpu-topology v5e:8x8  # real XLA:TPU AOT

Exit codes: 0 = all gates pass against the baseline, 1 = regression,
2 = usage/internal error. The CI twin is tests/test_shardcheck.py
(fast plans only; the ERNIE-10B plan is the slow tier / this CLI).

Gate semantics per plan (tolerances live in the baseline file):

- the sharded step must COMPILE (XLA:TPU additionally enforces the
  15.75 GiB/chip HBM budget at compile time when on a TPU topology);
- measured per-chip argument bytes must stay within tolerance of the
  baseline (ZeRO/TP sharding actually took — a broken spec tree shows
  up as an 8-64x jump here);
- the spec-tree projection to the target topology (e.g. v5e-64) must
  stay within tolerance AND under the plan's budget;
- the sharded-bytes fraction must not drop;
- the spec-tree hash must match (an intentional sharding change is
  re-baselined with --write-baseline, after review).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

GIB = 1024 ** 3
SCHEMA = 1

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tests", "fixtures",
                                "shardcheck_baseline.json")


# ------------------------------------------------------------ topology
def tpu_topology_mesh(topology_name: str, axes: dict, timeout_s: int = 90):
    """A mesh over a REAL XLA:TPU AOT topology (no chips attached).
    ``get_topology_desc`` can HANG when the host's TPU tunnel is wedged
    (observed: >120 s, not an exception), so availability is probed
    with the shared wedge-safe subprocess primitive
    (tools/_bench_common.bounded_subprocess_probe — the same helper
    bench.py's backend probe is built on) first; any failure returns
    None and the caller falls back to local devices."""
    from tools._bench_common import bounded_subprocess_probe
    probe = ("import jax; from jax.experimental import topologies; "
             f"topologies.get_topology_desc(platform='tpu', "
             f"topology_name={topology_name!r}); print('ok')")
    if not bounded_subprocess_probe(probe, timeout_s)["ok"]:
        return None
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topology_name)
    devs = np.array(topo.devices)
    names = list(axes.keys())
    degrees = [int(axes[n]) for n in names]
    if devs.size != int(np.prod(degrees)):
        return None
    return Mesh(devs.reshape(degrees), names)


def local_mesh(axes: dict):
    """Fallback mesh over the locally visible (virtual CPU) devices,
    scaling each axis down to what's available while keeping the axis
    NAMES stable so the spec tree is topology-independent."""
    import numpy as np
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    names = list(axes.keys())
    degrees = []
    avail = len(devs)
    for n in names:
        d = int(axes[n])
        while d > 1 and (avail % d != 0 or d > avail):
            d //= 2
        degrees.append(max(d, 1))
        avail //= max(degrees[-1], 1)
    total = int(np.prod(degrees))
    return Mesh(np.asarray(devs[:total]).reshape(degrees), names)


# ---------------------------------------------------------------- plans
def _train_step_for(model, optimizer, loss_fn, amp_level=None):
    from paddle_tpu.jit import TrainStep
    return TrainStep(model, loss_fn, optimizer, amp_level=amp_level)


def _plan_ernie(cfg_factory, target_axes, budget_gib, seq, batch_per_chip,
                moment_dtype="bfloat16", amp_level="O2",
                serving_mp=None):
    """ZeRO-3 ERNIE plan through the unified API: LazyGuard abstract
    params (~0 bytes of host RAM), ``apply_sharding(zero='p_g_os')``
    instead of the manual ``group_sharded_parallel`` wiring, AMP O2 +
    bf16 moments (BASELINE config 5)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import shard
    from paddle_tpu.models import ErnieForSequenceClassification

    def build(mesh):
        with paddle.LazyGuard():
            model = ErnieForSequenceClassification(cfg_factory())
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     moment_dtype=moment_dtype)
        specs = shard.apply_sharding(model, mesh=mesh, zero="p_g_os")
        step = _train_step_for(model, opt,
                               lambda o, y: F.cross_entropy(o, y),
                               amp_level=amp_level)
        n = mesh.devices.size
        bsz = batch_per_chip * n
        batch = (jax.ShapeDtypeStruct((bsz, seq), jnp.int64),
                 jax.ShapeDtypeStruct((bsz,), jnp.int64))

        def predict_lowered():
            from paddle_tpu.jit.functional import functional_call
            repl = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec())
            p_sh = shard.param_shardings(mesh, model.named_parameters())
            params_abs = {
                name: jax.ShapeDtypeStruct(tuple(p.shape), p._data.dtype,
                                           sharding=p_sh[name])
                for name, p in model.named_parameters()}
            buffers_abs = {
                name: jax.ShapeDtypeStruct(tuple(b.shape), b._data.dtype,
                                           sharding=repl)
                for name, b in model.named_buffers() if b is not None}
            ids = jax.ShapeDtypeStruct(
                (bsz, seq), jnp.int64,
                sharding=jax.sharding.NamedSharding(
                    mesh, shard.batch_spec(mesh)))

            def fwd(params, buffers, x):
                return functional_call(model, params, buffers, x,
                                       training=False)

            return jax.jit(fwd).lower(params_abs, buffers_abs, ids)

        return dict(model=model, step=step, batch=batch,
                    predict_lowered=predict_lowered, specs=specs)

    serving = None
    if serving_mp:
        # encoder-only (no cached decode), so the serving rows are
        # analytic: weight bytes through the name rules + KV geometry
        # from the config (what an mp-replica serving this family's
        # decoder variant would hold per chip)
        cfg = cfg_factory()
        serving = dict(
            axes={"mp": int(serving_mp)},
            geom=dict(num_layers=int(cfg.num_layers),
                      num_heads=int(cfg.num_heads),
                      head_dim=int(cfg.hidden_size) // int(cfg.num_heads),
                      max_seq_len=int(cfg.max_position_embeddings)))

    return dict(build=build, target_axes=dict(target_axes),
                budget_gib=budget_gib,
                mesh_axes={k: v for k, v in target_axes.items()},
                serving=serving)


def plan_ernie10b():
    from paddle_tpu.models import ernie_3_0_10b
    return _plan_ernie(
        lambda: ernie_3_0_10b(hidden_dropout_prob=0.0,
                              attention_probs_dropout_prob=0.0,
                              recompute=True),
        target_axes={"sharding": 64},   # v5e-64
        budget_gib=15.75, seq=1024, batch_per_chip=1,
        serving_mp=8)                   # one v5e-8 serving replica


def plan_ernie_tiny():
    """Fast CI plan: same code path as ernie10b at toy scale (the
    tier-1 gate; exercises LazyGuard + ZeRO-3 + AOT on the 8-device
    virtual CPU mesh)."""
    from paddle_tpu.models.ernie import ernie_tiny
    return _plan_ernie(
        lambda: ernie_tiny(),
        target_axes={"sharding": 8},
        budget_gib=None, seq=32, batch_per_chip=1,
        serving_mp=4)


def plan_gpt_tiny_tp():
    """TP + dp plan over the rule-table conventions (no ZeRO): the
    multi-chip-serving direction — params shard over 'mp' by the
    embedding/attention/MLP rules."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.distributed import shard
    from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                                   gpt_tiny)

    def build(mesh):
        paddle.seed(0)
        model = GPTForCausalLM(gpt_tiny(use_flash_attention=False))
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        specs = shard.apply_sharding(model, mesh=mesh)
        crit = GPTPretrainingCriterion()
        step = _train_step_for(model, opt, lambda o, y: crit(o, y))
        n_dp = mesh.shape.get("dp", 1)
        batch = (jax.ShapeDtypeStruct((2 * max(n_dp, 1), 32), jnp.int64),
                 jax.ShapeDtypeStruct((2 * max(n_dp, 1), 32), jnp.int64))
        return dict(model=model, step=step, batch=batch,
                    predict_lowered=None, specs=specs)

    # gpt_tiny has the cached-decode contract, so its serving section
    # ALSO AOT-compiles the sharded prefill+decode executables (the
    # tier-1 serving gate; ernie plans only get the analytic rows)
    return dict(build=build, target_axes={"dp": 2, "mp": 4},
                budget_gib=None, mesh_axes={"dp": 2, "mp": 4},
                serving=dict(axes={"mp": 4}))


PLANS = {
    "ernie10b": plan_ernie10b,
    "ernie_tiny_zero3": plan_ernie_tiny,
    "gpt_tiny_tp": plan_gpt_tiny_tp,
}

# the fast subset the test suite gates on every run
FAST_PLANS = ("ernie_tiny_zero3", "gpt_tiny_tp")


# ------------------------------------------------------------ execution
def _kv_projection(model, page_size: int = 16, max_batch: int = 8):
    """Serving KV-pool byte projection per pool dtype (the quantized-KV
    sizing story, gated like the sharding bytes): for each supported
    ``FLAGS_decode_kv_dtype`` this projects the engine's resident pool
    bytes — including the capacity factor the engine actually grants
    (sub-f32 dtypes get 2x pages, i.e. ~2x resident sequences) — so a
    regression in the quantized layout (a scale plane growing, the
    sizing rule regressing) trips the baseline gate.

    None for models without the cached-decode contract."""
    import numpy as np

    from paddle_tpu.ops.paged_attention import kv_pool_bytes
    from paddle_tpu.serving.generation.model_fns import \
        supports_cached_decode

    if not supports_cached_decode(model):
        return None
    spec = model.kv_cache_spec()
    nh, hd = spec["num_heads"], spec["head_dim"]
    layers = spec["num_layers"]
    pages_per_seq = -(-spec["max_seq_len"] // page_size)
    f32_tok = kv_pool_bytes(1, 1, nh, hd, None)
    dtypes = {}
    for dt in ("float32", "bfloat16", "int8"):
        tok = kv_pool_bytes(1, 1, nh, hd, dt)
        factor = max(1, min(2, f32_tok // max(tok, 1)))
        num_pages = 1 + max_batch * pages_per_seq * factor
        pool = layers * 2 * kv_pool_bytes(num_pages, page_size,
                                          nh, hd, dt)
        dtypes[dt] = {"token_bytes": int(tok),
                      "capacity_factor": int(factor),
                      "num_pages": int(num_pages),
                      "pool_bytes": int(pool)}
    ratio = dtypes["float32"]["token_bytes"] / \
        dtypes["int8"]["token_bytes"]
    return {"page_size": page_size, "max_batch": max_batch,
            "pages_per_seq": int(pages_per_seq),
            "head_dim": int(hd),
            "dtypes": dtypes,
            # per-token shrink 4/(1+4/D): 3.76x at D=64
            "int8_bytes_ratio": round(float(ratio), 4)}


def _serving_aot(model, serving_axes, page_size: int, max_batch: int):
    """AOT-compile the SHARDED prefill + decode executables exactly as
    the serving engine builds them — a ``CachedDecoder`` bound to a
    live ``{'mp': N}`` ``ServingMesh`` (serving/mesh.py), pools placed
    heads-sharded, weights placed by the spec tree — and return their
    per-chip memory plans. A spec tree that stops partitioning or a
    decode graph that stops compiling under a live mesh fails HERE at
    compile time, with no TPU attached. Uses the pure-JAX kernel path
    (the shadow-verification oracle): that is the canonical GSPMD
    partitioning the Pallas shard_map dispatch must agree with."""
    import jax.numpy as jnp

    from paddle_tpu.serving.generation.model_fns import CachedDecoder
    from paddle_tpu.serving.mesh import ServingMesh

    mesh = local_mesh(dict(serving_axes))
    smesh = ServingMesh(mesh)
    if not smesh.live:
        return None      # axes collapsed to 1 device — nothing to gate
    pages_per_seq = 2
    dec = CachedDecoder(model, max_batch=max_batch, page_size=page_size,
                        pages_per_seq=pages_per_seq, donate=False,
                        use_pallas=False, mesh=smesh)
    k, v = model.init_kv_pools(1 + max_batch * pages_per_seq, page_size)
    k, v = smesh.place_pools(k, v)
    b, s = max_batch, page_size
    ids = jnp.zeros((b, s), dtype=jnp.int32)
    plens = jnp.full((b,), s, dtype=jnp.int32)
    tables = jnp.zeros((b, pages_per_seq), dtype=jnp.int32)
    prefill = dec._prefill_jit.lower(
        dec._params, dec._buffers, ids, plens, tables, k, v).compile()
    tokens = jnp.zeros((b,), dtype=jnp.int32)
    positions = jnp.full((b,), s, dtype=jnp.int32)
    active = jnp.ones((b,), dtype=bool)
    ctx = jnp.full((b,), s + 1, dtype=jnp.int32)
    decode = dec._decode_jit.lower(
        dec._params, dec._buffers, tokens, positions, active, ctx,
        tables, k, v).compile()
    out = {}
    for site, comp in (("prefill", prefill), ("decode", decode)):
        ma = comp.memory_analysis()
        out[site] = {"args_bytes": int(ma.argument_size_in_bytes),
                     "temp_bytes": int(ma.temp_size_in_bytes)}
    out["n_chips_compiled"] = int(mesh.devices.size)
    out["mesh_axes"] = {a: int(d) for a, d in mesh.shape.items()}
    return out


def _serving_record(model, serving_axes: dict, geom=None,
                    page_size: int = 16, max_batch: int = 8):
    """Tensor-parallel SERVING projection at the replica's mesh degree
    (serving/mesh.py: fleet replica = mesh): per-chip weight bytes
    through the serving rule tables — the same name-based specs
    ``Predictor.attach_serving_mesh`` places by, NOT the training
    plan's ZeRO overrides — plus per-chip heads-sharded KV-pool bytes
    per supported ``FLAGS_decode_kv_dtype`` (the per-dtype projection
    above composed with the ``heads/mp`` split; host-side page
    bookkeeping is layout-agnostic, only device bytes divide). Models
    with the cached-decode contract additionally AOT-compile the
    sharded prefill + decode entry points (``_serving_aot``).

    ``geom`` supplies {num_layers, num_heads, head_dim, max_seq_len}
    for encoder-only models (ernie10b) that have no
    ``kv_cache_spec()``; their serving rows are analytic."""
    from paddle_tpu.distributed import shard
    from paddle_tpu.ops.paged_attention import kv_pool_bytes
    from paddle_tpu.serving.generation.model_fns import \
        supports_cached_decode

    mp = int(serving_axes.get("mp", 1))
    if geom is None:
        spec = model.kv_cache_spec()
        geom = {key: int(spec[key]) for key in
                ("num_layers", "num_heads", "head_dim", "max_seq_len")}
    nh, hd = geom["num_heads"], geom["head_dim"]
    heads_ok = mp <= 1 or nh % mp == 0

    rules = shard.default_rules()
    named = dict(model.named_parameters())
    specs = {n: rules.spec_for(n, tuple(p.shape))
             for n, p in named.items()}
    proj = shard.projected_bytes_per_chip(named, specs, serving_axes)

    pages_per_seq = -(-geom["max_seq_len"] // page_size)
    f32_tok = kv_pool_bytes(1, 1, nh, hd, None)
    per_dtype = {}
    for dt in ("float32", "bfloat16", "int8"):
        tok = kv_pool_bytes(1, 1, nh, hd, dt)
        factor = max(1, min(2, f32_tok // max(tok, 1)))
        num_pages = 1 + max_batch * pages_per_seq * factor
        pool = geom["num_layers"] * 2 * kv_pool_bytes(
            num_pages, page_size, nh, hd, dt)
        per_dtype[dt] = {
            "pool_bytes": int(pool),
            "per_chip_pool_bytes":
                int(pool // mp) if heads_ok and mp > 1 else int(pool),
        }
    rec = {
        "serving_axes": dict(serving_axes),
        "heads_shardable": bool(heads_ok),
        "num_heads": int(nh),
        "page_size": int(page_size),
        "max_batch": int(max_batch),
        "weights_per_chip_bytes": int(proj["total_bytes"]),
        "weights_spec_hash": shard.spec_tree_hash(specs),
        "kv_per_chip": per_dtype,
        "aot": None,
    }
    if supports_cached_decode(model) and heads_ok and mp > 1:
        rec["aot"] = _serving_aot(model, serving_axes, page_size,
                                  max_batch)
    return rec


def _mesh_kind(mesh) -> str:
    kinds = sorted({getattr(d, "device_kind", str(d))
                    for d in mesh.devices.flat})
    return f"{mesh.devices.size}x {'/'.join(kinds)}"


def _sharding_counts(specs, named_params, mesh_axes):
    import numpy as np
    sharded = repl = 0
    sharded_b = total_b = 0
    for name, p in named_params.items():
        spec = specs.get(name, ())
        shape = tuple(p.shape)
        n_elem = int(np.prod(shape)) if shape else 1
        dt = getattr(getattr(p, "_data", None), "dtype", "float32")
        nbytes = n_elem * np.dtype(str(dt)).itemsize
        total_b += nbytes
        if any(a is not None for a in spec):
            sharded += 1
            sharded_b += nbytes
        else:
            repl += 1
    return {"sharded_params": sharded, "replicated_params": repl,
            "sharded_fraction_bytes":
                round(sharded_b / total_b, 6) if total_b else 0.0}


def run_plan(name: str, tpu_topology: str = "") -> dict:
    """Build, AOT-compile and measure one plan; returns the record the
    baseline gate consumes."""
    import numpy as np

    from paddle_tpu.distributed import shard
    from paddle_tpu.distributed.mesh_utils import set_global_mesh

    plan = PLANS[name]()
    mesh = None
    topo_label = ""
    if tpu_topology:
        mesh = tpu_topology_mesh(tpu_topology, plan["mesh_axes"])
        topo_label = f"{tpu_topology} (AOT topology)"
    on_tpu_topo = mesh is not None
    if mesh is None:
        mesh = local_mesh(plan["mesh_axes"])
        topo_label = f"{_mesh_kind(mesh)} (local fallback)"
    set_global_mesh(mesh)
    try:
        built = plan["build"](mesh)
        step, model = built["step"], built["model"]
        compiled = step.aot_lower(mesh, *built["batch"])
        ma = compiled.memory_analysis()
        per_chip = {
            "args_bytes": int(ma.argument_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "out_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }
        predict = None
        if built.get("predict_lowered") is not None:
            pcomp = built["predict_lowered"]().compile()
            pma = pcomp.memory_analysis()
            predict = {"args_bytes": int(pma.argument_size_in_bytes),
                       "temp_bytes": int(pma.temp_size_in_bytes)}
        specs = built["specs"]
        named = dict(model.named_parameters())
        opt = step.optimizer
        opt_bytes = 0
        for an in opt._accum_names:
            # accumulator bytes per element (moments may be bf16)
            shape, dtype = opt._accum_spec(an, next(iter(named.values())))
            opt_bytes += np.dtype(str(dtype)).itemsize \
                if len(shape) else 0
        os_specs = {n: (getattr(p, "opt_state_spec", None) or
                        specs.get(n, ())) for n, p in named.items()}
        proj = shard.projected_bytes_per_chip(
            named, specs, plan["target_axes"],
            opt_bytes_per_param=opt_bytes, opt_specs=os_specs)
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        rec = {
            "schema": SCHEMA,
            "plan": name,
            "topology": topo_label,
            "on_tpu_topology": bool(on_tpu_topo),
            "n_chips_compiled": int(mesh.devices.size),
            "target_axes": plan["target_axes"],
            "budget_gib": plan["budget_gib"],
            "n_params": int(n_params),
            "per_chip": per_chip,
            "predict_per_chip": predict,
            "projected_per_chip": {
                "target_chips": int(np.prod(list(
                    plan["target_axes"].values()))),
                **proj,
                "model_state_gib": round(proj["total_bytes"] / GIB, 4),
            },
            "spec_tree_hash": shard.spec_tree_hash(
                shard.model_spec_tree(model)),
            "kv_projection": _kv_projection(model),
        }
        rec.update(_sharding_counts(specs, named, plan["target_axes"]))
        serving = plan.get("serving")
        if serving:
            # the serving path threads its mesh EXPLICITLY (engine
            # worker threads never see the thread-local global mesh) —
            # clear the training mesh first so the compile below sees
            # exactly what the engine sees
            set_global_mesh(None)
            rec["serving"] = _serving_record(model, serving["axes"],
                                             geom=serving.get("geom"))
        return rec
    finally:
        set_global_mesh(None)


# ----------------------------------------------------------------- gate
def gate_record(rec: dict, base: dict) -> list:
    """Failures of one plan record against its baseline entry. Empty
    list = pass."""
    fails = []
    tol = float(base.get("tolerance", 0.10))
    budget = rec.get("budget_gib")

    def _within(cur, ref, what):
        if ref and abs(cur - ref) > abs(ref) * tol:
            fails.append(f"{what}: {cur} vs baseline {ref} "
                         f"(>{tol:.0%} drift)")

    _within(rec["per_chip"]["args_bytes"],
            base["per_chip"]["args_bytes"], "per-chip argument bytes")
    _within(rec["projected_per_chip"]["total_bytes"],
            base["projected_per_chip"]["total_bytes"],
            "projected per-chip model-state bytes")
    if budget is not None and \
            rec["projected_per_chip"]["model_state_gib"] > budget:
        fails.append(
            f"projected model state "
            f"{rec['projected_per_chip']['model_state_gib']} GiB "
            f"exceeds the {budget} GiB/chip budget")
    if rec["sharded_fraction_bytes"] < \
            base["sharded_fraction_bytes"] - 0.01:
        fails.append(
            f"sharded-bytes fraction dropped: "
            f"{rec['sharded_fraction_bytes']} vs baseline "
            f"{base['sharded_fraction_bytes']}")
    if rec["spec_tree_hash"] != base["spec_tree_hash"]:
        fails.append(
            f"spec tree changed (hash {rec['spec_tree_hash'][:12]} vs "
            f"baseline {base['spec_tree_hash'][:12]}) — review the "
            f"sharding change, then --write-baseline")
    kv = rec.get("kv_projection")
    if kv is not None and base.get("kv_projection") is not None:
        bkv = base["kv_projection"]
        i8, f32 = kv["dtypes"]["int8"], kv["dtypes"]["float32"]
        _within(i8["pool_bytes"], bkv["dtypes"]["int8"]["pool_bytes"],
                "projected int8 KV pool bytes")
        # the quantized-KV contract: ~2x resident sequences that still
        # fit UNDER the f32 budget (the scale planes are the only
        # overhead, per-token shrink 4/(1+4/head_dim))
        if i8["capacity_factor"] < 2:
            fails.append(
                f"int8 capacity factor {i8['capacity_factor']} < 2 — "
                f"quantized pools no longer buy the ~2x headroom")
        if i8["pool_bytes"] > f32["pool_bytes"]:
            fails.append(
                f"int8 pool at 2x pages ({i8['pool_bytes']} B) "
                f"exceeds the f32 pool at 1x ({f32['pool_bytes']} B)")
        if kv["int8_bytes_ratio"] < bkv["int8_bytes_ratio"] - 0.01:
            fails.append(
                f"int8 per-token shrink regressed: "
                f"{kv['int8_bytes_ratio']}x vs baseline "
                f"{bkv['int8_bytes_ratio']}x")
    srv, bsrv = rec.get("serving"), base.get("serving")
    if srv is not None and bsrv is not None:
        _within(srv["weights_per_chip_bytes"],
                bsrv["weights_per_chip_bytes"],
                "serving per-chip weight bytes")
        for dt in ("float32", "int8"):
            _within(srv["kv_per_chip"][dt]["per_chip_pool_bytes"],
                    bsrv["kv_per_chip"][dt]["per_chip_pool_bytes"],
                    f"serving per-chip {dt} KV pool bytes")
        if bsrv.get("heads_shardable") and not srv.get("heads_shardable"):
            fails.append(
                f"serving heads axis no longer shardable: "
                f"{srv['num_heads']} heads do not divide "
                f"mp={srv['serving_axes'].get('mp')}")
        if srv["weights_spec_hash"] != bsrv["weights_spec_hash"]:
            fails.append(
                f"serving weight spec tree changed (hash "
                f"{srv['weights_spec_hash'][:12]} vs baseline "
                f"{bsrv['weights_spec_hash'][:12]}) — review the "
                f"rule-table change, then --write-baseline")
        if bsrv.get("aot") is not None:
            if srv.get("aot") is None:
                fails.append(
                    "sharded serving prefill+decode no longer "
                    "AOT-compile (baseline has an aot record)")
            else:
                for site in ("prefill", "decode"):
                    _within(srv["aot"][site]["args_bytes"],
                            bsrv["aot"][site]["args_bytes"],
                            f"sharded serving {site} per-chip "
                            f"argument bytes")
    return fails


def load_baseline(path: str) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return json.load(f).get("plans", {})


def write_baseline(path: str, records: dict, tolerance: float = 0.10):
    # merge: re-baselining a SUBSET (--plans) must not drop the other
    # plans' committed entries
    plans = dict(load_baseline(path))
    for name, rec in records.items():
        entry = dict(rec)
        entry["tolerance"] = tolerance
        plans[name] = entry
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": SCHEMA, "tool": "shardcheck",
                   "plans": plans}, f, indent=1, sort_keys=True)
        f.write("\n")


# ------------------------------------------------------------------ cli
def build_parser():
    p = argparse.ArgumentParser(prog="shardcheck", description=__doc__,
                                formatter_class=argparse.
                                RawDescriptionHelpFormatter)
    p.add_argument("--plans", default=None,
                   help=f"comma-separated subset of {sorted(PLANS)} "
                        f"(default: all)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument("--write-baseline", action="store_true")
    p.add_argument("--tolerance", type=float, default=0.10,
                   help="relative drift allowed on byte gates when "
                        "(re)writing the baseline")
    p.add_argument("--tpu-topology", default="",
                   help="try a real XLA:TPU AOT topology (e.g. "
                        "v5e:8x8); probed in a subprocess with a "
                        "timeout, falls back to local devices")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    names = list(PLANS)
    if args.plans:
        names = [n.strip() for n in args.plans.split(",") if n.strip()]
        unknown = set(names) - set(PLANS)
        if unknown:
            print(f"shardcheck: unknown plan(s) {sorted(unknown)} "
                  f"(have: {sorted(PLANS)})", file=sys.stderr)
            return 2

    records, failures = {}, {}
    for name in names:
        try:
            records[name] = run_plan(name, tpu_topology=args.tpu_topology)
        except Exception as e:  # noqa: BLE001 - a plan that cannot even
            failures[name] = [f"plan failed to compile: "  # compile IS
                              f"{type(e).__name__}: {e}"]  # the regression
    if args.write_baseline:
        if failures:
            for name, fs in failures.items():
                for f_ in fs:
                    print(f"shardcheck[{name}]: {f_}", file=sys.stderr)
            return 2
        write_baseline(args.baseline, records, args.tolerance)
        print(f"shardcheck: wrote baseline for {sorted(records)} to "
              f"{os.path.relpath(args.baseline, REPO_ROOT)}")
        return 0

    baseline = load_baseline(args.baseline)
    for name, rec in records.items():
        base = baseline.get(name)
        if base is None:
            failures.setdefault(name, []).append(
                "no baseline entry — run --write-baseline")
            continue
        fails = gate_record(rec, base)
        if fails:
            failures[name] = failures.get(name, []) + fails

    if args.as_json:
        print(json.dumps({"version": SCHEMA, "records": records,
                          "failures": failures}, indent=1,
                         sort_keys=True, default=repr))
        return 1 if failures else 0
    for name, rec in records.items():
        proj = rec["projected_per_chip"]
        print(f"shardcheck[{name}]: {rec['topology']}, "
              f"{rec['n_chips_compiled']} chips compiled, "
              f"args {rec['per_chip']['args_bytes'] / GIB:.3f} GiB/chip, "
              f"projected@{proj['target_chips']} "
              f"{proj['model_state_gib']:.3f} GiB model state"
              + (f" (budget {rec['budget_gib']} GiB)"
                 if rec["budget_gib"] else "")
              + f", specs {rec['spec_tree_hash'][:12]}")
        srv = rec.get("serving")
        if srv:
            i8 = srv["kv_per_chip"]["int8"]["per_chip_pool_bytes"]
            print(f"shardcheck[{name}]: serving "
                  f"mp={srv['serving_axes'].get('mp')}: weights "
                  f"{srv['weights_per_chip_bytes'] / GIB:.4f} GiB/chip, "
                  f"int8 KV {i8 / GIB:.4f} GiB/chip"
                  + (", sharded prefill+decode compiled"
                     if srv.get("aot") else ""))
    for name, fs in sorted(failures.items()):
        for f_ in fs:
            print(f"shardcheck[{name}]: FAIL: {f_}", file=sys.stderr)
    if not failures:
        print(f"shardcheck: {len(records)} plan(s) clean against "
              f"{os.path.relpath(args.baseline, REPO_ROOT)}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
