"""Repo tooling (benches, pdlint, fixture generators). A package so
the benches can share plumbing (``tools/_bench_common.py``) via
``from tools import _bench_common`` from the repo root."""
