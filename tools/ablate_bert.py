"""Step-time ablation for the BERT-base pretraining config (round-4
verdict item 4: the 45.2% vs gpt2-medium 51.8% MFU gap at s=512).

Same methodology as ablate_13b.py: knock one component out of the
compiled train step, re-time the WHOLE window, attribute end-to-end
(isolated microbenchmarks through the dispatch tunnel mislead).

Usage: python tools/ablate_bert.py [variant ...]
  base        unmodified step (b=32 s=512 AMP O2, bench.py config)
  noattn      self-attention replaced by identity (removes s^2 matmuls)
  nomlm       MLM decoder matmul over the 30k vocab replaced by a
              1024-wide slice (attributes the tied-embedding projection)
  notransform MLM transform Linear+LN removed (decoder kept)
  nonsp       NSP head + pooler removed from the loss
  noembed     token_type + position adds removed (word emb kept)
  nopooler    pooler tanh removed (NSP reads h[:,0] directly)
  gptcrit     single CE over full seq like the GPT criterion (removes
              the ignore_index masking machinery)
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(variant, steps=20, windows=3, batch=32, seq=512):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   BertPretrainingCriterion)
    from paddle_tpu.models import bert as bert_mod

    paddle.seed(0)
    cfg = BertConfig(hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    patches = []

    def patch(obj, name, repl):
        patches.append((obj, name, getattr(obj, name)))
        setattr(obj, name, repl)

    if variant == "noattn":
        cls = bert_mod.BertSelfAttention
        patch(cls, "forward", lambda self, x, attn_mask=None: x)
    elif variant == "noembed":
        cls = bert_mod.BertEmbeddings

        def word_only(self, input_ids, token_type_ids=None):
            return self.dropout(self.layer_norm(
                self.word_embeddings(input_ids)))
        patch(cls, "forward", word_only)
    elif variant == "nopooler":
        cls = bert_mod.BertPooler
        patch(cls, "forward", lambda self, h: h[:, 0])
    elif variant in ("nomlm", "notransform"):
        cls = BertForPretraining

        def fwd(self, input_ids, token_type_ids=None, attention_mask=None,
                _variant=variant):
            seq_out, pooled = self.bert(input_ids, token_type_ids,
                                        attention_mask)
            from paddle_tpu.tensor import linalg
            w = self.bert.embeddings.word_embeddings.weight
            if _variant == "notransform":
                h = seq_out
            else:
                h = self.transform_ln(F.gelu(self.transform(seq_out),
                                             approximate=True))
            if _variant == "nomlm":
                mlm_logits = linalg.matmul(h, w[:1024], transpose_y=True)
            else:
                mlm_logits = linalg.matmul(h, w, transpose_y=True)
            nsp_logits = self.nsp_head(pooled)
            return mlm_logits, nsp_logits
        patch(cls, "forward", fwd)

    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion(ignore_index=-1000)

    if variant == "gptcrit":
        def loss_fn(out, labels, nsp):
            mlm_logits, _ = out
            b, s, v = mlm_logits.shape
            return F.cross_entropy(mlm_logits.reshape([b * s, v]),
                                   labels.reshape([b * s]))
    elif variant == "nonsp":
        def loss_fn(out, labels, nsp):
            return crit(out, labels, None)
    else:
        def loss_fn(out, labels, nsp):
            return crit(out, labels, nsp)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = TrainStep(model, loss_fn, opt, amp_level="O2")
    rng = np.random.RandomState(0)
    vocab_hi = 1024 if variant == "nomlm" else cfg.vocab_size
    ids = paddle.to_tensor(
        rng.randint(0, vocab_hi, (batch, seq)).astype("int64"))
    nsp = paddle.to_tensor(rng.randint(0, 2, (batch,)).astype("int64"))
    try:
        loss = step.run_steps(steps, ids, ids, nsp, n_inputs=1)
        assert np.isfinite(float(loss.numpy()))
        best = float("inf")
        for _ in range(windows):
            t0 = time.perf_counter()
            loss = step.run_steps(steps, ids, ids, nsp, n_inputs=1)
            float(loss.numpy())
            best = min(best, (time.perf_counter() - t0) / steps)
    except Exception as e:
        print(f"{variant:12s}  FAILED: {type(e).__name__}: {e}")
        for obj, name, orig in patches:
            setattr(obj, name, orig)
        return None
    for obj, name, orig in patches:
        setattr(obj, name, orig)
    tok_s = batch * seq / best
    print(f"{variant:12s}  {best * 1e3:8.2f} ms/step  {tok_s:10.0f} tok/s")
    return best


if __name__ == "__main__":
    variants = sys.argv[1:] or ["base", "noattn", "nomlm", "notransform",
                                "nonsp", "noembed", "nopooler", "gptcrit"]
    base = None
    for v in variants:
        t = run(v)
        if v == "base":
            base = t
        elif base and t:
            print(f"{'':12s}  -> {v} saves {(base - t) / base * 100:.1f}% "
                  f"of the base step")
