"""Second-stage probe: where do the 11ms of decoder layer fwd+bwd go?

Components at bench shapes (b=16 s=512 h=1024 nh=16):
  - FFN only (2 matmuls + gelu) fwd+bwd
  - qkv/proj matmuls only fwd+bwd
  - dense attention core (einsum + f32 softmax) fwd+bwd
  - flash-fwd + XLA-recompute-bwd attention core (the model's path)
  - layernorm x2 fwd+bwd
Run: python -u tools/perf_probe2.py
"""
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))   # repo root (paddle_tpu)
sys.path.insert(0, _here)                    # tools/ (perf_probe helpers)

import jax
import jax.numpy as jnp
import numpy as np

from perf_probe import report, timed  # shared scan-timing harness

B, S, H, NH = 16, 512, 1024, 16
HD = H // NH
DT = jnp.bfloat16


def main():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, S, H), dtype=DT)
    w_f1 = jnp.asarray(rng.randn(H, 4 * H) * 0.02, DT)
    w_f2 = jnp.asarray(rng.randn(4 * H, H) * 0.02, DT)
    w_qkv = jnp.asarray(rng.randn(H, 3 * H) * 0.02, DT)
    w_o = jnp.asarray(rng.randn(H, H) * 0.02, DT)
    q = jnp.asarray(rng.randn(B, NH, S, HD), DT)
    k = jnp.asarray(rng.randn(B, NH, S, HD), DT)
    v = jnp.asarray(rng.randn(B, NH, S, HD), DT)

    # FFN fwd+bwd
    def ffn(a):
        f = jax.nn.gelu(a.reshape(B * S, H) @ w_f1) @ w_f2
        return f.astype(jnp.float32).sum()
    fl = 2 * B * S * 8 * H * H
    t = timed(jax.grad(ffn), x)
    report("FFN (8H^2) fwd+bwd", t, 3 * fl)

    # qkv + proj matmuls fwd+bwd
    def qkvp(a):
        z = a.reshape(B * S, H) @ w_qkv
        o = z[:, :H] @ w_o
        return o.astype(jnp.float32).sum()
    fl = 2 * B * S * 4 * H * H
    t = timed(jax.grad(qkvp), x)
    report("qkv+proj (4H^2) fwd+bwd", t, 3 * fl)

    # dense attention core fwd+bwd (f32 softmax like the module path)
    def dense_attn(qq, kk, vv):
        sc = jnp.einsum("bhqd,bhkd->bhqk", qq, kk) / np.sqrt(HD)
        mask = jnp.tril(jnp.ones((S, S), bool))
        sc = jnp.where(mask, sc, -1e9)
        p = jax.nn.softmax(sc.astype(jnp.float32), -1).astype(qq.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, vv).astype(jnp.float32).sum()
    fl = 4 * B * NH * S * S * HD
    t = timed(jax.grad(dense_attn, argnums=(0, 1, 2)), q, k, v)
    report("dense attn core fwd+bwd", t, 3 * fl)

    # Pallas flash kernels, fwd + dq/dkv bwd (custom_vjp)
    from paddle_tpu.ops.pallas_attention import mha

    def flash_loss(qq, kk, vv):
        return mha(qq, kk, vv, True, 1.0 / np.sqrt(HD), 128,
                   128).astype(jnp.float32).sum()
    try:
        t = timed(jax.grad(flash_loss, argnums=(0, 1, 2)), q, k, v)
        report("flash attn core fwd+bwd", t, 3 * fl)
    except Exception as e:
        print("flash probe unavailable:", type(e).__name__, str(e)[:160])

    # layernorm pair fwd+bwd
    g = jnp.ones((H,), jnp.float32)

    def lns(a):
        af = a.astype(jnp.float32)
        y = (af - af.mean(-1, keepdims=True)) / jnp.sqrt(
            af.var(-1, keepdims=True) + 1e-5) * g
        z = (y.astype(a.dtype).astype(jnp.float32)
             - y.mean(-1, keepdims=True)) * g
        return z.sum()
    t = timed(jax.grad(lns), x)
    report("2x layernorm fwd+bwd", t)


if __name__ == "__main__":
    main()
