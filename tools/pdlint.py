"""pdlint — run the paddle_tpu.analysis static analyzers from the CLI.

Reference analog: the compile step itself (typed gflags in
paddle/phi/core/flags.cc, tracer asserts) plus tools/check_api_compatible.py
style gates. Usage:

    python tools/pdlint.py                     # whole repo, text output
    python tools/pdlint.py paddle_tpu/serving  # a subtree
    python tools/pdlint.py --json              # machine-readable
    python tools/pdlint.py --sarif             # SARIF 2.1.0 document
    python tools/pdlint.py --changed-only origin/main   # incremental
    python tools/pdlint.py --analyzers flag_consistency
    python tools/pdlint.py --write-baseline    # re-baseline (after review!)
    python tools/pdlint.py --dump-flags        # runtime flags_snapshot()
    python tools/pdlint.py --dump-lock-graph   # lock-order graph as DOT

Findings already recorded in tests/fixtures/pdlint_baseline.json are
reported as baselined and do NOT fail the run. The baseline is a
RATCHET: a full default-tree run also fails when the baseline contains
fingerprints the repo no longer produces — fixed findings must be
pruned (--write-baseline does), so the file only ever shrinks.

``--changed-only REF`` still ANALYZES the whole tree (the engine's
call graph is interprocedural — a caller two files away can change
what is reachable) but REPORTS only findings in files changed vs the
git ref, plus untracked files. The ratchet is skipped in this mode:
a partial report cannot prove an entry stale.

Exit codes: 0 = clean against the baseline, 1 = new findings or stale
baseline entries, 2 = usage/internal error.

The CI twin is tests/test_static_analysis.py — it runs the same
analyzers over the same trees and fails on any non-baselined finding
and on any stale baseline entry.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pdlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*",
                   help="files/dirs to analyze (default: paddle_tpu "
                        "tools tests)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON document instead of text lines")
    p.add_argument("--sarif", action="store_true",
                   help="emit a SARIF 2.1.0 document (new findings "
                        "carry baselineState=new)")
    p.add_argument("--changed-only", default=None, metavar="REF",
                   help="report only findings in files changed vs this "
                        "git ref (analysis still runs repo-wide; "
                        "ratchet skipped)")
    p.add_argument("--analyzers", default=None,
                   help="comma-separated subset (default: all)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: tests/fixtures/"
                        "pdlint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: every finding is new")
    p.add_argument("--no-ratchet", action="store_true",
                   help="do not fail on stale baseline entries")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from this run's findings "
                        "and exit 0")
    p.add_argument("--list-analyzers", action="store_true")
    p.add_argument("--dump-flags", action="store_true",
                   help="print framework.flags.flags_snapshot() as "
                        "JSON and exit (runtime registry, not static)")
    p.add_argument("--dump-lock-graph", action="store_true",
                   help="print the static lock-order graph as "
                        "Graphviz DOT and exit (inversion cycles in "
                        "red); respects positional paths")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from paddle_tpu import analysis

    if args.list_analyzers:
        for name in analysis.analyzer_names():
            print(name)
        return 0
    if args.dump_flags:
        from paddle_tpu.framework.flags import flags_snapshot
        print(json.dumps(flags_snapshot(), indent=1, sort_keys=True))
        return 0

    analyzers = analysis.all_analyzers()
    if args.analyzers:
        wanted = {a.strip() for a in args.analyzers.split(",") if
                  a.strip()}
        unknown = wanted - set(analysis.analyzer_names())
        if unknown:
            print(f"pdlint: unknown analyzers {sorted(unknown)} "
                  f"(have: {analysis.analyzer_names()})",
                  file=sys.stderr)
            return 2
        analyzers = [a for a in analyzers if a.name in wanted]

    full_default_run = not args.paths
    paths = [os.path.abspath(p) for p in args.paths] or \
        analysis.default_paths(REPO_ROOT)
    for p in paths:
        if not os.path.exists(p):
            print(f"pdlint: no such path: {p}", file=sys.stderr)
            return 2

    if args.dump_lock_graph:
        from paddle_tpu.analysis import build_lock_graph
        from paddle_tpu.analysis.core import (iter_python_files,
                                              parse_files)
        files = parse_files(list(iter_python_files(paths,
                                                   root=REPO_ROOT)),
                            root=REPO_ROOT)
        sys.stdout.write(build_lock_graph(files).to_dot())
        return 0

    changed = None
    if args.changed_only is not None:
        changed = analysis.changed_files(args.changed_only, REPO_ROOT)
        if changed is None:
            print(f"pdlint: git could not diff against "
                  f"{args.changed_only!r}; running un-filtered",
                  file=sys.stderr)

    baseline_path = args.baseline or \
        analysis.default_baseline_path(REPO_ROOT)
    findings = analysis.run_analyzers(paths, analyzers, root=REPO_ROOT)

    if args.write_baseline:
        analysis.write_baseline(baseline_path, findings)
        print(f"pdlint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(baseline_path, REPO_ROOT)}")
        return 0

    baseline = {} if args.no_baseline else \
        analysis.load_baseline(baseline_path)

    reported = findings
    if changed is not None:
        reported = [f for f in findings if f.path in changed]
    new = analysis.filter_new(reported, baseline)

    # the ratchet: only a full default-tree, all-analyzer run can
    # prove a baseline entry dead (subtree/subset runs and
    # changed-only reports see a partial world)
    stale = []
    ratchet_active = (full_default_run and changed is None
                      and not args.no_baseline and not args.no_ratchet
                      and not args.analyzers)
    if ratchet_active:
        stale = analysis.stale_entries(findings, baseline)

    if args.sarif:
        print(json.dumps(analysis.to_sarif(
            reported, [a.name for a in analyzers], baseline),
            indent=1, sort_keys=True))
        return 1 if (new or stale) else 0

    if args.as_json:
        print(json.dumps({
            "version": 2,
            "analyzers": [a.name for a in analyzers],
            "baseline": os.path.relpath(baseline_path, REPO_ROOT),
            "baseline_size": len(baseline),
            "changed_only": args.changed_only,
            "counts": {"total": len(reported), "new": len(new),
                       "stale": len(stale)},
            "findings": [f.to_dict() for f in reported],
            "new": [f.fingerprint for f in new],
            "stale": stale,
        }, indent=1, sort_keys=True))
        return 1 if (new or stale) else 0

    new_fps = {f.fingerprint for f in new}
    for f in reported:
        suffix = "" if f.fingerprint in new_fps else "  [baselined]"
        print(f.format() + suffix)
    n_base = len(reported) - len(new)
    print(f"pdlint: {len(reported)} finding(s), {n_base} baselined, "
          f"{len(new)} new" + (f", {len(stale)} stale baseline "
                               f"entry(ies)" if stale else ""))
    if new:
        print("pdlint: new findings — fix them, or (after review) "
              "refresh the baseline with --write-baseline",
              file=sys.stderr)
    if stale:
        print("pdlint: RATCHET — these baselined findings no longer "
              "exist; prune them (the baseline only shrinks):",
              file=sys.stderr)
        for fp in stale:
            print(f"  {fp}", file=sys.stderr)
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
