"""pdlint — run the paddle_tpu.analysis static analyzers from the CLI.

Reference analog: the compile step itself (typed gflags in
paddle/phi/core/flags.cc, tracer asserts) plus tools/check_api_compatible.py
style gates. Usage:

    python tools/pdlint.py                     # whole repo, text output
    python tools/pdlint.py paddle_tpu/serving  # a subtree
    python tools/pdlint.py --json              # machine-readable
    python tools/pdlint.py --analyzers flag_consistency
    python tools/pdlint.py --write-baseline    # re-baseline (after review!)
    python tools/pdlint.py --dump-flags        # runtime flags_snapshot()

Findings already recorded in tests/fixtures/pdlint_baseline.json are
reported as baselined and do NOT fail the run. Exit codes: 0 = clean
against the baseline, 1 = new findings, 2 = usage/internal error.

The CI twin is tests/test_static_analysis.py — it runs the same
analyzers over the same trees and fails on any non-baselined finding.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pdlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*",
                   help="files/dirs to analyze (default: paddle_tpu "
                        "tools tests)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON document instead of text lines")
    p.add_argument("--analyzers", default=None,
                   help="comma-separated subset (default: all)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: tests/fixtures/"
                        "pdlint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: every finding is new")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from this run's findings "
                        "and exit 0")
    p.add_argument("--list-analyzers", action="store_true")
    p.add_argument("--dump-flags", action="store_true",
                   help="print framework.flags.flags_snapshot() as "
                        "JSON and exit (runtime registry, not static)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from paddle_tpu import analysis

    if args.list_analyzers:
        for name in analysis.analyzer_names():
            print(name)
        return 0
    if args.dump_flags:
        from paddle_tpu.framework.flags import flags_snapshot
        print(json.dumps(flags_snapshot(), indent=1, sort_keys=True))
        return 0

    analyzers = analysis.all_analyzers()
    if args.analyzers:
        wanted = {a.strip() for a in args.analyzers.split(",") if
                  a.strip()}
        unknown = wanted - set(analysis.analyzer_names())
        if unknown:
            print(f"pdlint: unknown analyzers {sorted(unknown)} "
                  f"(have: {analysis.analyzer_names()})",
                  file=sys.stderr)
            return 2
        analyzers = [a for a in analyzers if a.name in wanted]

    paths = [os.path.abspath(p) for p in args.paths] or \
        analysis.default_paths(REPO_ROOT)
    for p in paths:
        if not os.path.exists(p):
            print(f"pdlint: no such path: {p}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or \
        analysis.default_baseline_path(REPO_ROOT)
    findings = analysis.run_analyzers(paths, analyzers, root=REPO_ROOT)

    if args.write_baseline:
        analysis.write_baseline(baseline_path, findings)
        print(f"pdlint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(baseline_path, REPO_ROOT)}")
        return 0

    baseline = {} if args.no_baseline else \
        analysis.load_baseline(baseline_path)
    new = analysis.filter_new(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "version": 1,
            "analyzers": [a.name for a in analyzers],
            "baseline": os.path.relpath(baseline_path, REPO_ROOT),
            "baseline_size": len(baseline),
            "counts": {"total": len(findings), "new": len(new)},
            "findings": [f.to_dict() for f in findings],
            "new": [f.fingerprint for f in new],
        }, indent=1, sort_keys=True))
        return 1 if new else 0

    new_fps = {f.fingerprint for f in new}
    for f in findings:
        suffix = "" if f.fingerprint in new_fps else "  [baselined]"
        print(f.format() + suffix)
    n_base = len(findings) - len(new)
    print(f"pdlint: {len(findings)} finding(s), {n_base} baselined, "
          f"{len(new)} new")
    if new:
        print("pdlint: new findings — fix them, or (after review) "
              "refresh the baseline with --write-baseline",
              file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
