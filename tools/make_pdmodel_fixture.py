"""Generate a GENUINE reference-format .pdmodel/.pdiparams fixture.

The ProgramDesc bytes are produced by Google protobuf (protoc --python_out
on the reference's framework.proto) — an implementation independent of the
hand-rolled wire decoder in paddle_tpu/static/pdmodel.py — so the interop
test is not circular. The parameter stream follows the save_combine layout
(lod_tensor.cc SerializeToStream): u32 version | u64 lod levels | u32
tensor version | i32 desc_len | TensorDesc proto | raw data, tensors in
sorted-name order.

Run:  python tools/make_pdmodel_fixture.py
Writes tests/fixtures/mlp.pdmodel, mlp.pdiparams, mlp_expected.npz
"""
import os
import struct
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROTO = "/root/reference/paddle/fluid/framework/framework.proto"
FIXDIR = os.path.join(REPO, "tests", "fixtures")

FP32 = 5
INT64 = 3
LOD_TENSOR = 7
FEED_MINIBATCH = 9
FETCH_LIST = 10


def gen_pb2():
    tmp = tempfile.mkdtemp()
    import shutil
    shutil.copy(PROTO, os.path.join(tmp, "framework.proto"))
    subprocess.run(["protoc", "--python_out=.", "framework.proto"],
                   cwd=tmp, check=True)
    sys.path.insert(0, tmp)
    import framework_pb2
    return framework_pb2


def add_var(block, name, vtype, dtype=FP32, dims=None, persistable=False):
    v = block.vars.add()
    v.name = name
    v.type.type = vtype
    if dims is not None:
        v.type.lod_tensor.tensor.data_type = dtype
        v.type.lod_tensor.tensor.dims.extend(dims)
    v.persistable = persistable
    return v


def add_op(block, fp, op_type, inputs, outputs, attrs=None):
    op = block.ops.add()
    op.type = op_type
    for slot, args in inputs.items():
        iv = op.inputs.add()
        iv.parameter = slot
        iv.arguments.extend(args)
    for slot, args in outputs.items():
        ov = op.outputs.add()
        ov.parameter = slot
        ov.arguments.extend(args)
    for aname, aval in (attrs or {}).items():
        a = op.attrs.add()
        a.name = aname
        if isinstance(aval, bool):
            a.type = fp.BOOLEAN
            a.b = aval
        elif isinstance(aval, int):
            a.type = fp.INT
            a.i = aval
        elif isinstance(aval, float):
            a.type = fp.FLOAT
            a.f = aval
        elif isinstance(aval, str):
            a.type = fp.STRING
            a.s = aval
        elif isinstance(aval, list) and all(
                isinstance(x, int) for x in aval):
            a.type = fp.INTS
            a.ints.extend(aval)
        else:
            raise TypeError(f"attr {aname}: {aval!r}")
    return op


def serialize_tensor(fp, arr: np.ndarray) -> bytes:
    """save_combine per-tensor layout (tensor_util.cc TensorToStream)."""
    desc = fp.VarType.TensorDesc()
    desc.data_type = FP32 if arr.dtype == np.float32 else INT64
    desc.dims.extend(arr.shape)
    desc_bytes = desc.SerializeToString()
    out = struct.pack("<I", 0)            # LoDTensor version
    out += struct.pack("<Q", 0)           # lod levels
    out += struct.pack("<I", 0)           # tensor version
    out += struct.pack("<i", len(desc_bytes))
    out += desc_bytes
    out += arr.tobytes()
    return out


def main():
    fp = gen_pb2()
    rng = np.random.RandomState(42)
    params = {
        "fc_0.w_0": rng.randn(4, 8).astype(np.float32),
        "fc_0.b_0": rng.randn(8).astype(np.float32),
        "fc_1.w_0": rng.randn(8, 3).astype(np.float32),
        "fc_1.b_0": rng.randn(3).astype(np.float32),
    }

    prog = fp.ProgramDesc()
    block = prog.blocks.add()
    block.idx = 0
    block.parent_idx = -1

    add_var(block, "feed", FEED_MINIBATCH)
    add_var(block, "x", LOD_TENSOR, FP32, [-1, 4])
    for n, a in params.items():
        add_var(block, n, LOD_TENSOR, FP32, list(a.shape), persistable=True)
    for n in ("t0", "t1", "t2", "t3", "t4", "softmax_out"):
        add_var(block, n, LOD_TENSOR, FP32, [-1, 8])
    add_var(block, "fetch", FETCH_LIST)

    add_op(block, fp, "feed", {"X": ["feed"]}, {"Out": ["x"]}, {"col": 0})
    add_op(block, fp, "mul", {"X": ["x"], "Y": ["fc_0.w_0"]},
           {"Out": ["t0"]}, {"x_num_col_dims": 1, "y_num_col_dims": 1})
    add_op(block, fp, "elementwise_add", {"X": ["t0"], "Y": ["fc_0.b_0"]},
           {"Out": ["t1"]}, {"axis": 1})
    add_op(block, fp, "relu", {"X": ["t1"]}, {"Out": ["t2"]})
    add_op(block, fp, "matmul_v2", {"X": ["t2"], "Y": ["fc_1.w_0"]},
           {"Out": ["t3"]}, {"trans_x": False, "trans_y": False})
    add_op(block, fp, "elementwise_add", {"X": ["t3"], "Y": ["fc_1.b_0"]},
           {"Out": ["t4"]}, {"axis": 1})
    add_op(block, fp, "softmax", {"X": ["t4"]}, {"Out": ["softmax_out"]},
           {"axis": -1})
    add_op(block, fp, "fetch", {"X": ["softmax_out"]}, {"Out": ["fetch"]},
           {"col": 0})
    prog.version.version = 1

    os.makedirs(FIXDIR, exist_ok=True)
    with open(os.path.join(FIXDIR, "mlp.pdmodel"), "wb") as f:
        f.write(prog.SerializeToString())
    with open(os.path.join(FIXDIR, "mlp.pdiparams"), "wb") as f:
        for name in sorted(params):
            f.write(serialize_tensor(fp, params[name]))

    # expected output with plain numpy
    x = np.linspace(-1, 1, 8, dtype=np.float32).reshape(2, 4)
    h = np.maximum(x @ params["fc_0.w_0"] + params["fc_0.b_0"], 0)
    logits = h @ params["fc_1.w_0"] + params["fc_1.b_0"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    np.savez(os.path.join(FIXDIR, "mlp_expected.npz"), x=x, probs=probs)

    make_cnn_fixture(fp)
    print("fixture written:", sorted(os.listdir(FIXDIR)))


def make_cnn_fixture(fp):
    """Second fixture: conv2d → batch_norm (inference) → relu → pool2d →
    flatten → layer_norm → scale, exercising the structural converters
    beyond the MLP's matmul family."""
    rng = np.random.RandomState(7)
    params = {
        "bn.b": rng.randn(4).astype(np.float32),
        "bn.m": rng.rand(4).astype(np.float32),
        "bn.v": (rng.rand(4) + 0.5).astype(np.float32),
        "bn.w": rng.randn(4).astype(np.float32),
        "conv.w": (rng.randn(4, 2, 3, 3) * 0.5).astype(np.float32),
    }

    prog = fp.ProgramDesc()
    block = prog.blocks.add()
    block.idx = 0
    block.parent_idx = -1
    add_var(block, "feed", FEED_MINIBATCH)
    add_var(block, "img", LOD_TENSOR, FP32, [-1, 2, 8, 8])
    for n, a in params.items():
        add_var(block, n, LOD_TENSOR, FP32, list(a.shape), persistable=True)
    for n in ("c0", "b0", "r0", "p0", "f0", "l0", "out"):
        add_var(block, n, LOD_TENSOR, FP32, [-1, 4])
    add_var(block, "fetch", FETCH_LIST)

    add_op(block, fp, "feed", {"X": ["feed"]}, {"Out": ["img"]}, {"col": 0})
    add_op(block, fp, "conv2d", {"Input": ["img"], "Filter": ["conv.w"]},
           {"Output": ["c0"]},
           {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
            "groups": 1})
    add_op(block, fp, "batch_norm",
           {"X": ["c0"], "Scale": ["bn.w"], "Bias": ["bn.b"],
            "Mean": ["bn.m"], "Variance": ["bn.v"]},
           {"Y": ["b0"], "MeanOut": ["bn.m"], "VarianceOut": ["bn.v"],
            "SavedMean": [], "SavedVariance": []},
           {"epsilon": 1e-5, "data_layout": "NCHW", "is_test": True})
    add_op(block, fp, "relu", {"X": ["b0"]}, {"Out": ["r0"]})
    add_op(block, fp, "pool2d", {"X": ["r0"]}, {"Out": ["p0"]},
           {"pooling_type": "avg", "global_pooling": True})
    add_op(block, fp, "flatten_contiguous_range", {"X": ["p0"]},
           {"Out": ["f0"], "XShape": []},
           {"start_axis": 1, "stop_axis": 3})
    add_op(block, fp, "layer_norm", {"X": ["f0"]},
           {"Y": ["l0"], "Mean": [], "Variance": []},
           {"epsilon": 1e-5, "begin_norm_axis": 1})
    add_op(block, fp, "scale", {"X": ["l0"]}, {"Out": ["out"]},
           {"scale": 2.0, "bias": 1.0, "bias_after_scale": True})
    add_op(block, fp, "fetch", {"X": ["out"]}, {"Out": ["fetch"]},
           {"col": 0})
    prog.version.version = 1

    with open(os.path.join(FIXDIR, "cnn.pdmodel"), "wb") as f:
        f.write(prog.SerializeToString())
    with open(os.path.join(FIXDIR, "cnn.pdiparams"), "wb") as f:
        for name in sorted(params):
            f.write(serialize_tensor(fp, params[name]))

    # expected with plain numpy
    img = rng.randn(2, 2, 8, 8).astype(np.float32)
    pad = np.pad(img, ((0, 0), (0, 0), (1, 1), (1, 1)))
    c = np.zeros((2, 4, 8, 8), np.float32)
    for o in range(4):
        for i in range(2):
            for y in range(8):
                for xx in range(8):
                    c[:, o, y, xx] += np.einsum(
                        "bij,ij->b", pad[:, i, y:y + 3, xx:xx + 3],
                        params["conv.w"][o, i])
    shape = (1, 4, 1, 1)
    b = (c - params["bn.m"].reshape(shape)) / np.sqrt(
        params["bn.v"].reshape(shape) + 1e-5) * \
        params["bn.w"].reshape(shape) + params["bn.b"].reshape(shape)
    r = np.maximum(b, 0)
    p = r.mean(axis=(2, 3), keepdims=True).reshape(2, 4)
    ln = (p - p.mean(-1, keepdims=True)) / np.sqrt(
        p.var(-1, keepdims=True) + 1e-5)
    out = ln * 2.0 + 1.0
    np.savez(os.path.join(FIXDIR, "cnn_expected.npz"), img=img, out=out)


if __name__ == "__main__":
    main()
